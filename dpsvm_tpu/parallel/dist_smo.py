"""Distributed SMO: SPMD shard_map over a 1-D device mesh.

TPU-native redesign of the reference's MPI layer (``svmTrainMain.cpp``,
SURVEY CS-1). Mapping:

* contiguous example shards, ceil(n/P) per rank with the remainder on the
  last (``svmTrainMain.cpp:367-384``)  ->  equal shards of n padded to a
  multiple of P, with a validity mask (padding belongs to no index set);
* per-iteration ``MPI::Allgather`` of each rank's 4-float extreme tuple +
  identical global scan on every rank (``svmTrainMain.cpp:244-277``)  ->
  ``lax.all_gather`` of per-shard (b_hi, b_lo) / (i_hi, i_lo) inside the
  compiled loop + replicated argmin/argmax (first shard wins ties, like
  the reference's strict comparisons);
* every rank holding the FULL dataset (``svmTrainMain.cpp:180``,
  ``svmTrain.cu:344``)  ->  X row-sharded over the mesh (``shard_x=True``;
  this removes the reference's O(n d) per-device memory ceiling), with the
  two working rows broadcast by a masked ``psum`` of a (2, d+3) pack —
  rows plus the owner's (x^2, y, alpha) scalars. ``shard_x=False``
  reproduces the replicated layout;
* the whole loop stays inside ONE jitted program: no per-iteration MPI or
  host latency, the collectives ride ICI/DCN between XLA ops.

alpha and f are always sharded (the reference shards f but replicates
alpha, ``svmTrain.cu:349,374-380``; sharding both is strictly less state).
eta's three kernel evaluations are read from the owner shards' K rows via
a second tiny psum — the reference recomputes them on the host with CBLAS
each iteration (``svmTrainMain.cpp:282``, a quirk this design deletes).

Single-device parity: with P=1 every collective degenerates to identity
and this program computes exactly solver/smo.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from dpsvm_tpu.config import SENTINEL, SVMConfig, TrainResult
from dpsvm_tpu.ops.kernels import (KernelSpec, host_row_stats,
                                   host_row_norms_sq,
                                   kdiag_from_norms, rows_from_dots)
from dpsvm_tpu.ops.rowcache import RowCache, cache_fetch_pair
from dpsvm_tpu.ops.selection import (masked_extrema, masked_extrema_packed,
                                     masked_scores_and_masks)
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.ops.update import alpha_pair_step
from dpsvm_tpu.parallel.mesh import (SHARD_AXIS, make_data_mesh,
                                     pcast_varying, shard_map_compat,
                                     shard_probe, to_host)
from dpsvm_tpu.solver.driver import (device_sv_count, host_training_loop,
                                     pack_stats, resume_state)


class DistCarry(NamedTuple):
    alpha: jax.Array    # (n_pad,) sharded over "shard"
    f: jax.Array        # (n_pad,) sharded
    b_hi: jax.Array     # () replicated
    b_lo: jax.Array     # () replicated
    n_iter: jax.Array   # () i32 replicated
    # Per-shard kernel-row cache (the reference's cache is a component of
    # the MPI trainer's hot path, one myCache per rank caching the
    # shard's dot-product segment keyed by global working index —
    # svmTrain.cu:142-156, cache.cu:49-60). Empty (0 lines) when off.
    ck: jax.Array       # (P*lines,) i32 keys, sharded; -1 = empty line
    cs: jax.Array       # (P*lines,) i32 last-use stamps, sharded
    cr: jax.Array       # (P*lines, n_s) f32 dot rows, sharded on axis 0
    # Cache outcome counters (replicated-equal: the key sequence is the
    # same on every shard, so every shard observes the identical
    # hit/miss stream — the counter matches the single-device count).
    # Ride the packed-stats transfer; see docs/OBSERVABILITY.md.
    ch: jax.Array       # () i32 cache hits
    cm: jax.Array       # () i32 cache misses


def _owner_read(arr: jax.Array, local_idx, is_owner) -> jax.Array:
    """Value of arr[local_idx] on the owning shard, zeros elsewhere
    (to be summed across shards by the caller's psum)."""
    return jnp.where(is_owner, arr[local_idx], jnp.zeros_like(arr[local_idx]))


def _weighted_box(c: float, weights, ys):
    """(c_box for the masks, c(y) for the clips): scalar when the class
    weights are (1, 1) — the exact reference path — else derived from y
    (the working indices' y values are already broadcast, so weighted
    clips need no extra collective)."""
    wp, wn = weights
    if wp == 1.0 and wn == 1.0:
        return c, lambda y_sel: jnp.float32(c)
    c_box = jnp.where(ys > 0, jnp.float32(c * wp), jnp.float32(c * wn))
    return c_box, lambda y_sel: jnp.where(y_sel > 0, jnp.float32(c * wp),
                                          jnp.float32(c * wn))


def _local_slice(xs, x2s, rank, n_per_shard, shard_x: bool):
    """This shard's (n_s, d) X slice and x^2 segment: identity when X is
    already sharded, a dynamic row-slice when X is replicated."""
    if shard_x:
        return xs, x2s
    return (lax.dynamic_slice_in_dim(xs, rank * n_per_shard, n_per_shard),
            lax.dynamic_slice_in_dim(x2s, rank * n_per_shard, n_per_shard))


def _eta_kernel_entries(k_local, loc_hi, own_hi, loc_lo, own_lo):
    """(K(hi,hi), K(lo,lo), K(hi,lo)) from the shards' local kernel rows
    via one masked-psum of the owners' reads."""
    k_pack = lax.psum(jnp.stack([
        _owner_read(k_local[0], loc_hi, own_hi),
        _owner_read(k_local[1], loc_lo, own_lo),
        _owner_read(k_local[0], loc_lo, own_lo),
    ]), SHARD_AXIS)
    return k_pack[0], k_pack[1], k_pack[2]


def _broadcast_row(xs, ys, x2s, alpha_s, loc, own, gi, *, shard_x: bool):
    """(row, x2, y, alpha) of global index gi, replicated on every shard
    via one masked psum (the owner contributes, everyone sums)."""
    if shard_x:
        pack = jnp.concatenate([
            _owner_read(xs, loc, own),
            jnp.stack([_owner_read(x2s, loc, own),
                       _owner_read(ys, loc, own),
                       _owner_read(alpha_s, loc, own)])])
        pack = lax.psum(pack, SHARD_AXIS)
        d = xs.shape[-1]
        return pack[:d], pack[d], pack[d + 1], pack[d + 2]
    scal = jnp.stack([jnp.where(own, x2s[gi], 0.0),
                      _owner_read(ys, loc, own),
                      _owner_read(alpha_s, loc, own)])
    scal = lax.psum(scal, SHARD_AXIS)
    return xs[gi], scal[0], scal[1], scal[2]


def _dist_step_wss2(carry: DistCarry, xs, ys, x2s, valid, *,
                    c: float, kspec: KernelSpec, n_per_shard: int,
                    shard_x: bool, precision,
                    weights=(1.0, 1.0),
                    pairwise_clip: bool = False) -> DistCarry:
    """One second-order (WSS2) iteration over the mesh: the hi row is
    broadcast first, every shard scores its local violators against it,
    and the lo index comes from a second tiny all_gather. Two row
    broadcasts instead of first-order's packed one."""
    alpha_s, f_s = carry.alpha, carry.f
    rank = lax.axis_index(SHARD_AXIS)
    c_box, c_of_y = _weighted_box(c, weights, ys)

    f_up_l, f_low_l, _, in_low = masked_scores_and_masks(
        alpha_s, ys, f_s, c_box, valid)

    # --- phase 1: global i_hi (argmin f over I_up) + stopping b_lo ---
    li_hi = jnp.argmin(f_up_l)
    lb_hi = f_up_l[li_hi]
    lb_lo = jnp.max(f_low_l)                       # stopping gap only
    gi_hi = li_hi.astype(jnp.int32) + rank * n_per_shard
    fv = lax.all_gather(jnp.stack([lb_hi, lb_lo]), SHARD_AXIS)     # (P, 2)
    iv = lax.all_gather(gi_hi, SHARD_AXIS)                         # (P,)
    p_hi = jnp.argmin(fv[:, 0])
    b_hi = fv[p_hi, 0]
    b_lo = fv[jnp.argmax(fv[:, 1]), 1]
    i_hi_g = iv[p_hi]
    loc_hi = i_hi_g - p_hi * n_per_shard
    own_hi = rank == p_hi

    row_hi, x2_hi, y_hi, a_hi = _broadcast_row(
        xs, ys, x2s, alpha_s, loc_hi, own_hi, i_hi_g, shard_x=shard_x)

    # --- phase 2: WSS2 lo choice against the hi kernel row ---
    # WSS2 consumes K only shard-locally (scores + owner reads), so with
    # replicated X slice this shard's rows BEFORE the matmul — unlike
    # first-order, which reads K at global indices.
    xs_l, x2s_l = _local_slice(xs, x2s, rank, n_per_shard, shard_x)

    def local_k_row(row, w2):
        if kspec.kind == "precomputed":
            # the broadcast row IS the kernel row; take this shard's
            # column segment
            return lax.dynamic_slice_in_dim(row, rank * n_per_shard,
                                            n_per_shard)
        dots = jnp.matmul(row[None, :], xs_l.T, precision=precision)
        return rows_from_dots(dots, w2[None], x2s_l, kspec)[0]

    k_hi = local_k_row(row_hi, x2_hi)                              # (n_s,)
    bb = f_low_l - b_hi
    if kspec.is_rbf:
        a = jnp.maximum(2.0 - 2.0 * k_hi, 1e-12)
    else:
        # a_j = K(hi,hi) + K(jj) - 2 K(hi,j); the hi diagonal comes from
        # the already-broadcast x2_hi, the local diagonal from this
        # shard's norms — no extra collective.
        a = jnp.maximum(kdiag_from_norms(x2_hi, kspec)
                        + kdiag_from_norms(x2s_l, kspec) - 2.0 * k_hi,
                        1e-12)
    obj = jnp.where(in_low & (bb > 0), bb * bb / a, -1.0)
    li_lo = jnp.argmax(obj)
    lo_pack = jnp.stack([obj[li_lo], f_low_l[li_lo]])
    gi_lo = li_lo.astype(jnp.int32) + rank * n_per_shard
    ov = lax.all_gather(lo_pack, SHARD_AXIS)                       # (P, 2)
    ig = lax.all_gather(gi_lo, SHARD_AXIS)
    p_lo = jnp.argmax(ov[:, 0])
    b_lo_sel = ov[p_lo, 1]
    i_lo_g = ig[p_lo]
    loc_lo = i_lo_g - p_lo * n_per_shard
    own_lo = rank == p_lo

    row_lo, x2_lo, y_lo, a_lo = _broadcast_row(
        xs, ys, x2s, alpha_s, loc_lo, own_lo, i_lo_g, shard_x=shard_x)
    k_lo = local_k_row(row_lo, x2_lo)

    # --- eta from the owner shards' K values, clamped (WSS2 steers
    # toward small-eta pairs; see solver/smo.py). Deliberately a third
    # psum: recomputing the pair kernels replicated from the broadcast
    # rows would avoid it but gives a different reduction order than the
    # oracle's K-row reads, breaking the bit-level trajectory parity the
    # tests assert — and one ~µs scalar collective is noise next to the
    # two serial (1,d)@(d,n_s) matmuls in this body. ---
    k_hh, k_ll, k_hl = _eta_kernel_entries((k_hi, k_lo), loc_hi, own_hi,
                                           loc_lo, own_lo)
    eta = jnp.maximum(k_hh + k_ll - 2.0 * k_hl, 1e-12)

    a_hi_n, a_lo_n = alpha_pair_step(a_hi, a_lo, y_hi, y_lo, b_hi,
                                     b_lo_sel, eta, c_of_y(y_hi),
                                     c_of_y(y_lo), pairwise_clip)

    alpha_s = alpha_s.at[loc_lo].set(
        jnp.where(own_lo, a_lo_n, alpha_s[loc_lo]))
    alpha_s = alpha_s.at[loc_hi].set(
        jnp.where(own_hi, a_hi_n, alpha_s[loc_hi]))

    f_s = (f_s + (a_hi_n - a_hi) * y_hi * k_hi
               + (a_lo_n - a_lo) * y_lo * k_lo)

    return DistCarry(alpha_s, f_s, b_hi, b_lo, carry.n_iter + 1,
                     carry.ck, carry.cs, carry.cr, carry.ch, carry.cm)


def _dist_step(carry: DistCarry, xs, ys, x2s, valid, *,
               c: float, kspec: KernelSpec, n_per_shard: int,
               shard_x: bool, precision, weights=(1.0, 1.0),
               use_cache: bool = False,
               packed_select: bool = False,
               pairwise_clip: bool = False,
               guard_eta: bool = False) -> DistCarry:
    """One SMO iteration, SPMD over the mesh axis. xs/x2s are per-shard
    slices when shard_x else full replicated arrays."""
    alpha_s, f_s = carry.alpha, carry.f
    rank = lax.axis_index(SHARD_AXIS)
    c_box, c_of_y = _weighted_box(c, weights, ys)

    # --- local working-set extrema (CS-2) ---
    select = masked_extrema_packed if packed_select else masked_extrema
    li_hi, lb_hi, li_lo, lb_lo = select(alpha_s, ys, f_s, c_box, valid)
    gi_hi = li_hi.astype(jnp.int32) + rank * n_per_shard
    gi_lo = li_lo.astype(jnp.int32) + rank * n_per_shard

    # --- global selection: all_gather + replicated scan (CS-1) ---
    fv = lax.all_gather(jnp.stack([lb_hi, lb_lo]), SHARD_AXIS)     # (Pn, 2)
    iv = lax.all_gather(jnp.stack([gi_hi, gi_lo]), SHARD_AXIS)     # (Pn, 2)
    p_hi = jnp.argmin(fv[:, 0])
    p_lo = jnp.argmax(fv[:, 1])
    b_hi = fv[p_hi, 0]
    b_lo = fv[p_lo, 1]
    i_hi_g = iv[p_hi, 0]
    i_lo_g = iv[p_lo, 1]

    loc_hi = i_hi_g - p_hi * n_per_shard
    loc_lo = i_lo_g - p_lo * n_per_shard
    own_hi = rank == p_hi
    own_lo = rank == p_lo

    # --- broadcast working rows + owner scalars ---
    # One psum: (2, d+3) when X rows live on their owner shard, (2, 3)
    # scalars-only when X is replicated (rows readable locally).
    if shard_x:
        x2_hi_c = _owner_read(x2s, loc_hi, own_hi)
        x2_lo_c = _owner_read(x2s, loc_lo, own_lo)
    else:
        x2_hi_c = jnp.where(own_hi, x2s[i_hi_g], 0.0)
        x2_lo_c = jnp.where(own_lo, x2s[i_lo_g], 0.0)
    scalars = jnp.stack([
        jnp.stack([x2_hi_c,
                   _owner_read(ys, loc_hi, own_hi),
                   _owner_read(alpha_s, loc_hi, own_hi)]),
        jnp.stack([x2_lo_c,
                   _owner_read(ys, loc_lo, own_lo),
                   _owner_read(alpha_s, loc_lo, own_lo)]),
    ])                                                          # (2, 3)
    if shard_x:
        pack = jnp.concatenate([
            jnp.stack([_owner_read(xs, loc_hi, own_hi),
                       _owner_read(xs, loc_lo, own_lo)]),
            scalars], axis=1)
        pack = lax.psum(pack, SHARD_AXIS)
        d = xs.shape[-1]
        rows = pack[:, :d]
        scalars = pack[:, d:]
    else:
        rows = jnp.stack([xs[i_hi_g], xs[i_lo_g]])
        scalars = lax.psum(scalars, SHARD_AXIS)
    w2 = scalars[:, 0]
    y_hi, y_lo = scalars[0, 1], scalars[1, 1]
    a_hi, a_lo = scalars[0, 2], scalars[1, 2]

    # --- kernel rows on the local slice: (2, d) @ (d, n_s) (CS-3) ---
    cache_out = (carry.ck, carry.cs, carry.cr, carry.ch, carry.cm)
    if kspec.kind == "precomputed":
        # The gathered working rows carry the FULL (column-padded)
        # kernel row: eta entries are global-index reads and the local
        # segment is a slice. (config rejects the cache here.)
        k_hh = rows[0, i_hi_g]
        k_ll = rows[1, i_lo_g]
        k_hl = rows[0, i_lo_g]
        k_local = lax.dynamic_slice_in_dim(
            rows, rank * n_per_shard, n_per_shard, axis=1)
    elif use_cache:
        # Per-shard dot-row cache keyed on GLOBAL working index, exactly
        # the reference's per-rank layout (cache line = this shard's
        # segment, key = global index — svmTrain.cu:142-156). The key
        # sequence is replicated, so hit/miss is uniform across shards
        # and the miss matmul has no collective inside the lax.cond.
        # n_iter is the LRU tick (one fetch per iteration).
        xs_l, x2s_l = _local_slice(xs, x2s, rank, n_per_shard, shard_x)
        cache = RowCache(keys=carry.ck, stamps=carry.cs, rows=carry.cr,
                         tick=carry.n_iter, hits=carry.ch,
                         misses=carry.cm)
        dots, cache = cache_fetch_pair(
            cache, i_hi_g, i_lo_g,
            lambda: jnp.matmul(rows, xs_l.T, precision=precision))
        cache_out = (cache.keys, cache.stamps, cache.rows, cache.hits,
                     cache.misses)
        k_local = rows_from_dots(dots, w2, x2s_l, kspec)           # (2, n_s)
        k_hh, k_ll, k_hl = _eta_kernel_entries(k_local, loc_hi, own_hi,
                                               loc_lo, own_lo)
    elif shard_x:
        dots = jnp.matmul(rows, xs.T, precision=precision)
        k_local = rows_from_dots(dots, w2, x2s, kspec)             # (2, n_s)
        k_hh, k_ll, k_hl = _eta_kernel_entries(k_local, loc_hi, own_hi,
                                               loc_lo, own_lo)
    else:
        dots = jnp.matmul(rows, xs.T, precision=precision)
        k_full = rows_from_dots(dots, w2, x2s, kspec)              # (2, n_pad)
        k_hh = k_full[0, i_hi_g]
        k_ll = k_full[1, i_lo_g]
        k_hl = k_full[0, i_lo_g]
        k_local = lax.dynamic_slice_in_dim(
            k_full, rank * n_per_shard, n_per_shard, axis=1)
    eta = k_hh + k_ll - 2.0 * k_hl
    if guard_eta:
        # TAU clamp for f_init-seeded problems (SVR twin rows make
        # eta == 0 reachable — see solver/smo.py); the classification
        # path keeps the reference's raw division for bit parity.
        eta = jnp.maximum(eta, 1e-12)

    # --- alpha update: replicated scalar math (svmTrainMain.cpp:282-295) ---
    a_hi_n, a_lo_n = alpha_pair_step(a_hi, a_lo, y_hi, y_lo, b_hi, b_lo,
                                     eta, c_of_y(y_hi), c_of_y(y_lo),
                                     pairwise_clip)

    # masked writeback, lo then hi (train_step2 order, svmTrain.cu:491-492)
    alpha_s = alpha_s.at[loc_lo].set(
        jnp.where(own_lo, a_lo_n, alpha_s[loc_lo]))
    alpha_s = alpha_s.at[loc_hi].set(
        jnp.where(own_hi, a_hi_n, alpha_s[loc_hi]))

    f_s = (f_s + (a_hi_n - a_hi) * y_hi * k_local[0]
               + (a_lo_n - a_lo) * y_lo * k_local[1])

    return DistCarry(alpha_s, f_s, b_hi, b_lo, carry.n_iter + 1,
                     *cache_out)


@functools.lru_cache(maxsize=16)
def _build_dist_runner(mesh: jax.sharding.Mesh, c: float, kspec,
                       epsilon: float, n_per_shard: int, shard_x: bool,
                       precision_name: str, second_order: bool = False,
                       weights=(1.0, 1.0), use_cache: bool = False,
                       packed_select: bool = False,
                       pairwise_clip: bool = False,
                       guard_eta: bool = False):
    precision = getattr(lax.Precision, precision_name)
    kspec = KernelSpec.coerce(kspec)
    x_spec = P(SHARD_AXIS) if shard_x else P()
    if second_order:
        step = _dist_step_wss2
        extra = {"pairwise_clip": pairwise_clip}
    else:
        step = _dist_step
        extra = {"use_cache": use_cache, "packed_select": packed_select,
                 "pairwise_clip": pairwise_clip, "guard_eta": guard_eta}

    def run(carry: DistCarry, xs, ys, x2s, valid, limit):
        def cond(s: DistCarry):
            return (s.b_lo > s.b_hi + 2.0 * epsilon) & (s.n_iter < limit)

        def body(s: DistCarry):
            return step(s, xs, ys, x2s, valid, c=c, kspec=kspec,
                        n_per_shard=n_per_shard, shard_x=shard_x,
                        precision=precision, weights=weights, **extra)

        # b_hi/b_lo come out of the loop body via all_gather (and the
        # cache counters via the sharded key compare), which types them
        # as axis-varying under shard_map's VMA checks; mark the
        # initial values to match, and fold back to invariant (the
        # values are replicated-equal by construction) with a pmax on
        # exit. pcast_varying is the identity on jax versions without
        # VMA typing (parallel/mesh.py).
        carry = carry._replace(
            b_hi=pcast_varying(carry.b_hi),
            b_lo=pcast_varying(carry.b_lo),
            ch=pcast_varying(carry.ch),
            cm=pcast_varying(carry.cm))
        out = lax.while_loop(cond, body, carry)
        # The probe reads the PRE-pmax per-shard values: the fold below
        # erases exactly the cross-shard disagreement the desync
        # detector watches for (parallel/mesh.shard_probe).
        probe = shard_probe(out.n_iter, out.b_lo, out.b_hi)
        return out._replace(b_hi=lax.pmax(out.b_hi, SHARD_AXIS),
                            b_lo=lax.pmax(out.b_lo, SHARD_AXIS),
                            ch=lax.pmax(out.ch, SHARD_AXIS),
                            cm=lax.pmax(out.cm, SHARD_AXIS)), probe

    carry_specs = DistCarry(alpha=P(SHARD_AXIS), f=P(SHARD_AXIS),
                            b_hi=P(), b_lo=P(), n_iter=P(),
                            ck=P(SHARD_AXIS), cs=P(SHARD_AXIS),
                            cr=P(SHARD_AXIS, None), ch=P(), cm=P())
    mapped = shard_map_compat(
        run, mesh=mesh,
        in_specs=(carry_specs, x_spec, P(SHARD_AXIS), x_spec, P(SHARD_AXIS),
                  P()),
        out_specs=(carry_specs, P(SHARD_AXIS)))

    def run_with_stats(carry, xs, ys, x2s, valid, limit):
        final, probe = mapped(carry, xs, ys, x2s, valid, limit)
        # Packed poll scalars + telemetry counters as a second output
        # of the SAME compiled program — one D2H transfer per chunk, no
        # auxiliary XLA program (solver/driver.py "Poll economics").
        # The SV count reduces the global sharded alpha; padding rows
        # hold alpha == 0 and never count. The (3P,) per-shard probe
        # tail rides the same array (resilience/elastic.py).
        return final, jnp.concatenate([
            pack_stats(final.n_iter, final.b_lo, final.b_hi,
                       n_sv=device_sv_count(final.alpha),
                       cache_hits=final.ch,
                       cache_misses=final.cm), probe])

    return jax.jit(run_with_stats, donate_argnums=(0,))


class DistInputs(NamedTuple):
    """Everything the pad-and-shard protocol produces, shared by the
    pair (this module) and decomposition (parallel/dist_decomp.py)
    distributed trainers."""
    n_s: int
    xd: jax.Array
    yd: jax.Array
    x2: jax.Array
    validd: jax.Array
    shard: NamedSharding
    repl: NamedSharding
    init: tuple            # (alpha0, f0, b_hi, b_lo, n_iter)


def prepare_distributed_inputs(x, y, config: SVMConfig, mesh, ckpt,
                               f_init, alpha_init,
                               capacity: "Optional[int]" = None
                               ) -> DistInputs:
    """Pad n to the mesh, place X/y/x2/valid with the configured
    layout, and seed (alpha, f, b's, n_iter) from the checkpoint or the
    (possibly f_init/alpha_init-overridden) classification init.

    ``capacity``: pad the row count up to at least this many rows
    before the mesh-divisibility rounding (the shrinking manager's
    power-of-two buckets, which keep the SPMD program count bounded at
    log2(n) across shrink cycles). Capacity rows are zero and masked
    invalid exactly like the mesh-divisibility padding — this is the
    ONE place that builds padded distributed inputs, so callers never
    pre-pad. Default: no extra rows.
    """
    n, d = x.shape
    p = mesh.devices.size
    n_cap = max(n, int(capacity or 0))
    n_pad = ((n_cap + p - 1) // p) * p
    if config.kernel == "precomputed":
        # pad K on BOTH axes: per-shard column segments must exist for
        # the padded rows too (padded entries are masked invalid and
        # their zero kernel values leave f unchanged)
        xp = np.zeros((n_pad, n_pad), np.float32)
        xp[:n, :n] = x
    else:
        xp = np.zeros((n_pad, d), np.float32)
        xp[:n] = x
    # x2 (squared norms, or diag(K) for precomputed) computed on the
    # UNPADDED rows then zero-padded: diagonal() on the padded matrix
    # would be wrong (row-padding makes it non-square).
    x2p = np.zeros((n_pad,), np.float32)
    x2p[:n] = host_row_stats(x, config.kernel_spec(d))
    yp = np.zeros((n_pad,), np.float32)
    yp[:n] = y
    valid = np.arange(n_pad) < n

    shard = NamedSharding(mesh, P(SHARD_AXIS))
    repl = NamedSharding(mesh, P())
    x_sharding = shard if config.shard_x else repl

    if ckpt is not None:
        a0 = np.zeros((n_pad,), np.float32)
        a0[:n] = ckpt.alpha
        f0 = np.zeros((n_pad,), np.float32)
        f0[:n] = ckpt.f
        init = (a0, f0, ckpt.b_hi, ckpt.b_lo, ckpt.n_iter)
    else:
        f0 = -yp
        if f_init is not None:
            f0 = np.zeros((n_pad,), np.float32)
            f0[:n] = np.asarray(f_init, np.float32)
        a0 = np.zeros((n_pad,), np.float32)
        if alpha_init is not None:
            a0[:n] = np.asarray(alpha_init, np.float32)
        init = (a0, f0, -SENTINEL, SENTINEL, 0)
    return DistInputs(
        n_s=n_pad // p,
        xd=jax.device_put(xp, x_sharding),
        yd=jax.device_put(yp, shard),
        x2=jax.device_put(x2p, x_sharding),
        validd=jax.device_put(valid, shard),
        shard=shard, repl=repl, init=init)


def train_distributed(x: np.ndarray, y: np.ndarray, config: SVMConfig,
                      mesh: Optional[jax.sharding.Mesh] = None,
                      f_init: Optional[np.ndarray] = None,
                      alpha_init: Optional[np.ndarray] = None,
                      guard_eta: bool = False) -> TrainResult:
    """Train over a 1-D device mesh; data arrives/leaves as host NumPy.

    ``f_init`` overrides the classification f = -y initialization (SVR
    seeding — see solver/smo.py); checkpoint resume takes precedence.
    """
    config.validate()
    n, d = x.shape
    if mesh is None:
        mesh = make_data_mesh(config.shards)
    p = mesh.devices.size      # the mesh, not config.shards, is authoritative
    gamma = float(config.resolve_gamma(d))
    kspec = config.kernel_spec(d)
    eps = float(config.epsilon)

    ckpt = resume_state(config, n, d, gamma, shards=p)
    di = prepare_distributed_inputs(x, y, config, mesh, ckpt,
                                    f_init, alpha_init)
    n_s = di.n_s
    xd, yd, x2, validd = di.xd, di.yd, di.x2, di.validd
    shard, repl, init = di.shard, di.repl, di.init
    # Per-shard row cache: `lines` lines per shard (the reference's -s is
    # per-rank lines too, svmTrainMain.cpp:70); 0 disables. Resume starts
    # cold — the checkpoint holds only (alpha, f), like the reference's
    # model file holds no cache.
    lines = int(config.cache_size)
    row_shard = NamedSharding(mesh, P(SHARD_AXIS, None))
    # Host NumPy + device_put: no per-constructor XLA programs (see
    # solver/smo.init_carry on tunneled-TPU first-compile costs).
    carry = DistCarry(
        alpha=jax.device_put(np.asarray(init[0], np.float32), shard),
        f=jax.device_put(np.asarray(init[1], np.float32), shard),
        b_hi=jax.device_put(np.float32(init[2]), repl),
        b_lo=jax.device_put(np.float32(init[3]), repl),
        n_iter=jax.device_put(np.int32(init[4]), repl),
        ck=jax.device_put(np.full((p * lines,), -1, np.int32), shard),
        cs=jax.device_put(np.zeros((p * lines,), np.int32), shard),
        cr=jax.device_put(np.zeros((p * lines, n_s), np.float32),
                          row_shard),
        ch=jax.device_put(np.int32(0), repl),
        cm=jax.device_put(np.int32(0), repl),
    )

    runner = compilewatch.instrument(
        _build_dist_runner(mesh, float(config.c), kspec, eps, n_s,
                           bool(config.shard_x),
                           config.matmul_precision.upper(),
                           config.selection == "second-order",
                           (float(config.weight_pos),
                            float(config.weight_neg)),
                           use_cache=lines > 0,
                           packed_select=config.select_impl == "packed",
                           pairwise_clip=config.clip == "pairwise",
                           guard_eta=guard_eta),
        f"dist-smo-chunk/p={p}")

    def step_chunk(c, lim):
        limit = jax.device_put(np.int32(lim), repl)
        return runner(c, xd, yd, x2, validd, limit)

    def carry_from_ckpt(ck):
        # Divergence-rollback hook (docs/ROBUSTNESS.md): rebuild the
        # sharded carry from checkpoint state — same padding as the
        # resume path above, cache cold like a resume.
        a0 = np.zeros((n_s * p,), np.float32)
        a0[:n] = np.asarray(ck.alpha, np.float32)
        f0 = np.zeros((n_s * p,), np.float32)
        f0[:n] = np.asarray(ck.f, np.float32)
        return DistCarry(
            alpha=jax.device_put(a0, shard),
            f=jax.device_put(f0, shard),
            b_hi=jax.device_put(np.float32(ck.b_hi), repl),
            b_lo=jax.device_put(np.float32(ck.b_lo), repl),
            n_iter=jax.device_put(np.int32(ck.n_iter), repl),
            ck=jax.device_put(np.full((p * lines,), -1, np.int32), shard),
            cs=jax.device_put(np.zeros((p * lines,), np.int32), shard),
            cr=jax.device_put(np.zeros((p * lines, n_s), np.float32),
                              row_shard),
            ch=jax.device_put(np.int32(0), repl),
            cm=jax.device_put(np.int32(0), repl))

    return host_training_loop(
        config, gamma, n, d, carry,
        step_chunk=step_chunk,
        carry_to_host=lambda c: (to_host(c.alpha)[:n],
                                 to_host(c.f)[:n]),
        it0=int(init[4]),
        carry_from_ckpt=carry_from_ckpt,
        shards=p,
    )

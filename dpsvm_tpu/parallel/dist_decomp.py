"""Distributed large-working-set decomposition: the MXU path over a mesh.

Composes the two round-3 solvers: solver/decomp.py's outer round
(top-q violators -> one big kernel-block fetch -> WSS2 inner subsolve ->
rank-q update) runs SPMD over the 1-D data mesh of parallel/dist_smo.py.
Per outer round:

  * each shard takes its LOCAL top-q/2 violators per side (lax.top_k on
    the masked scores — q/2 values + global indices per shard);
  * one ``all_gather`` merges them; the global top-q/2 per side is a
    replicated stable argsort over the P*q/2 candidates. Stability plus
    contiguous sharding makes the merged selection EQUAL to the
    single-device top_k on EQUAL scores (ties resolve to the lowest
    global index in both), so the distributed trajectory matches
    single-device decomp whenever the kernel entries agree bitwise —
    exact at shapes where the sharded (q, d) @ (d, n_s) fetch tiles the
    d-reduction the same way (asserted in the driver dryrun), while at
    other shapes one ulp of fetch difference can flip a near-tie and
    the contract is the equal-quality eps-KKT point of
    tests/test_dist_decomp.py;
  * the (q, d) working-set rows + their (alpha, f, x2, y) ride ONE
    masked ``psum`` pack from their owner shards (the q-row
    generalization of dist_smo's pair broadcast);
  * K_WW is computed replicated in exact f32 (q^2 d FLOPs — noise), and
    the inner WSS2 subsolve runs REPLICATED on every shard: identical
    inputs, identical arithmetic, zero communication;
  * the heavy (q, d) @ (d, n_s) block fetch and the rank-q f update are
    local to each shard — the part worth scaling is the part that
    scales;
  * outer stopping extrema ride the same all_gather that selection uses.

Communication per round: one (P, q/2, 2)-ish all_gather pair (KBs) and
one (q, d+4) psum (~q*d floats; 3 MB at q=1024, d=784) — ICI noise next
to the sharded matmul. Everything lives inside ONE jitted while_loop,
chunk-polled by the shared host driver.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.observability import compilewatch
from dpsvm_tpu.ops.kernels import KernelSpec, rows_from_dots
from dpsvm_tpu.ops.selection import masked_scores_and_masks
from dpsvm_tpu.parallel.dist_smo import (_local_slice,
                                         prepare_distributed_inputs)
from dpsvm_tpu.parallel.mesh import (SHARD_AXIS, make_data_mesh,
                                     pcast_varying, shard_map_compat,
                                     shard_probe, to_host)
from dpsvm_tpu.solver.decomp import inner_subsolve
from dpsvm_tpu.solver.driver import (device_sv_count, host_training_loop,
                                     pack_stats, resume_state)


class DistDecompCarry(NamedTuple):
    alpha: jax.Array    # (n_pad,) sharded
    f: jax.Array        # (n_pad,) sharded
    b_hi: jax.Array     # () replicated-equal
    b_lo: jax.Array     # ()
    n_iter: jax.Array   # () i32 cumulative inner pair-updates
    rounds: jax.Array   # () i32 outer rounds (telemetry, rides the
                        # packed stats — solver/decomp.DecompCarry)


def _merged_top(vals_l, gidx_l, k):
    """Global top-k from per-shard top-k candidates, matching
    single-device ``lax.top_k`` exactly: all_gather the per-shard
    (value, global index) lists and take the k best by a STABLE argsort.
    Per-shard candidates are value-sorted with lower-local-index ties
    (top_k's rule) and shards are contiguous, so the flattened order of
    any value tie is ascending global index — stability then reproduces
    the single-device lowest-index-wins selection."""
    vals = lax.all_gather(vals_l, SHARD_AXIS).reshape(-1)     # (P*k,)
    gidx = lax.all_gather(gidx_l, SHARD_AXIS).reshape(-1)
    order = jnp.argsort(-vals, stable=True)[:k]
    return vals[order], gidx[order]


def _gather_w(wi, active, xs, ys, x2s, alpha_s, f_s, rank, n_per_shard,
              shard_x: bool):
    """Replicated (rows, x2, y, alpha, f) of the working set from the
    owner shards via one masked psum pack ((q, d+4); rows omitted from
    the pack when X is replicated)."""
    loc = jnp.clip(wi - rank * n_per_shard, 0, n_per_shard - 1)
    own = active & (wi // n_per_shard == rank)
    ownf = own.astype(jnp.float32)[:, None]
    # Owner-masked per-slot scalars (x2, y, alpha, f), each (q,).
    x2_c = (x2s[loc] if shard_x
            else x2s[jnp.clip(wi, 0, x2s.shape[0] - 1)])
    cols = jnp.stack([
        jnp.where(own, x2_c, 0.0),
        jnp.where(own, ys[loc], 0.0),
        jnp.where(own, alpha_s[loc], 0.0),
        jnp.where(own, f_s[loc], 0.0),
    ], axis=1)                                               # (q, 4)
    if shard_x:
        pack = jnp.concatenate([xs[loc] * ownf, cols], axis=1)
        pack = lax.psum(pack, SHARD_AXIS)
        d = xs.shape[-1]
        rows, cols = pack[:, :d], pack[:, d:]
    else:
        cols = lax.psum(cols, SHARD_AXIS)
        rows = xs[jnp.clip(wi, 0, xs.shape[0] - 1)]
        rows = jnp.where(active[:, None], rows, 0.0)
    return rows, cols[:, 0], cols[:, 1], cols[:, 2], cols[:, 3]


def _dist_decomp_step(carry: DistDecompCarry, xs, ys, x2s, valid, *,
                      c: float, kspec: KernelSpec, n_per_shard: int,
                      n_true, q: int, inner_cap: int,
                      epsilon: float, limit, shard_x: bool, precision,
                      weights=(1.0, 1.0),
                      pairwise_clip: bool = False) -> DistDecompCarry:
    """One distributed outer round. ``n_true`` (traced i32) is the
    count of valid rows — global indices >= it are capacity padding."""
    alpha_s, f_s = carry.alpha, carry.f
    rank = lax.axis_index(SHARD_AXIS)
    wp, wn = weights
    if wp != 1.0 or wn != 1.0:
        c_box = jnp.where(ys > 0, jnp.float32(c * wp), jnp.float32(c * wn))
    else:
        c_box = c

    # --- selection: local top-q/2 per side, merged replicated ---------
    f_up_l, f_low_l, _, _ = masked_scores_and_masks(alpha_s, ys, f_s,
                                                    c_box, valid)
    k2 = q // 2
    # A shard can hold fewer rows than q/2 (tiny n, many shards): each
    # shard then contributes its whole slice; the train wrapper's
    # q <= 2n clamp guarantees P * k_loc >= q/2 merged candidates.
    k_loc = min(k2, n_per_shard)
    base = rank * n_per_shard
    uv_l, ui_l = lax.top_k(-f_up_l, k_loc)
    lv_l, li_l = lax.top_k(f_low_l, k_loc)
    uv, ui = _merged_top(uv_l, ui_l.astype(jnp.int32) + base, k2)
    lv, li = _merged_top(lv_l, li_l.astype(jnp.int32) + base, k2)
    b_hi = -uv[0]
    b_lo = lv[0]

    w_idx = jnp.unique(jnp.concatenate([ui, li]), size=q,
                       fill_value=jnp.int32(-1))
    active = (w_idx >= 0) & (w_idx < n_true)
    wi = jnp.where(active, w_idx, 0)

    # --- working-set state from the owner shards ----------------------
    rows, x2_w, y_w, a_w0, f_w0 = _gather_w(
        wi, active, xs, ys, x2s, alpha_s, f_s, rank, n_per_shard, shard_x)
    if wp != 1.0 or wn != 1.0:
        c_w = jnp.where(y_w > 0, jnp.float32(c * wp), jnp.float32(c * wn))
    else:
        c_w = jnp.full((q,), jnp.float32(c))

    # --- exact f32 subproblem kernel (see solver/decomp.py on why the
    # block must NOT be gathered from bf16 dots) -----------------------
    if kspec.kind == "precomputed":
        # gathered K rows: the (q, q) block is a column gather of the
        # stored exact values (global indices)
        k_ww = rows[:, wi]
    else:
        dots_ww = jnp.matmul(rows, rows.T,
                             precision=lax.Precision.HIGHEST)
        k_ww = rows_from_dots(dots_ww, x2_w, x2_w, kspec)

    # --- replicated WSS2 inner subsolve (identical on every shard,
    # zero communication; shared with solver/decomp.py) ----------------
    step_cap = jnp.minimum(jnp.int32(inner_cap), limit - carry.n_iter)

    # Every seed field is replicated-equal across shards by
    # construction, but shard_map's VMA typing tags psum-derived values
    # as axis-varying; the while_loop carry must enter with uniformly-
    # varying types (pcast_varying passes already-varying leaves
    # through, and is the identity on jax versions without VMA typing —
    # parallel/mesh.py).
    inner = inner_subsolve(
        k_ww, y_w, c_w, a_w0, f_w0, active, epsilon=epsilon,
        step_cap=step_cap, pairwise_clip=pairwise_clip,
        seed_transform=lambda s: jax.tree.map(pcast_varying, s))

    # --- rank-q application, shard-local (the (q, n_s) fetch sits
    # after the subsolve so its epilogue fuses into the weighted
    # row-sum — see solver/decomp.py) ----------------------------------
    dalpha = jnp.where(active, inner.a - a_w0, 0.0)
    own = active & (wi // n_per_shard == rank)
    loc = jnp.clip(wi - rank * n_per_shard, 0, n_per_shard - 1)
    alpha_s = alpha_s.at[loc].add(jnp.where(own, dalpha, 0.0))

    if kspec.kind == "precomputed":
        k_wn = lax.dynamic_slice_in_dim(rows, rank * n_per_shard,
                                        n_per_shard, axis=1)
    else:
        xs_l, x2s_l = _local_slice(xs, x2s, rank, n_per_shard, shard_x)
        dots = jnp.matmul(rows, xs_l.T, precision=precision)  # (q, n_s)
        k_wn = rows_from_dots(dots, x2_w, x2s_l, kspec)
    f_s = f_s + jnp.matmul((dalpha * y_w)[None, :], k_wn,
                           precision=precision)[0]

    return DistDecompCarry(alpha_s, f_s, b_hi, b_lo,
                           carry.n_iter + inner.t, carry.rounds + 1)


@functools.lru_cache(maxsize=16)
def _build_dist_decomp_runner(mesh: jax.sharding.Mesh, c: float, kspec,
                              epsilon: float, n_per_shard: int,
                              q: int, inner_cap: int,
                              shard_x: bool, precision_name: str,
                              weights=(1.0, 1.0),
                              pairwise_clip: bool = False):
    precision = getattr(lax.Precision, precision_name)
    kspec = KernelSpec.coerce(kspec)
    x_spec = P(SHARD_AXIS) if shard_x else P()

    def run(carry: DistDecompCarry, xs, ys, x2s, valid, limit):
        # The valid-row count, derived from the data rather than baked
        # into the program: the shrinking manager re-enters here with
        # many different active counts at the same padded capacity, and
        # a static count would recompile per count (it is also part of
        # the builder's lru_cache key no longer).
        n_true = lax.psum(jnp.sum(valid.astype(jnp.int32)), SHARD_AXIS)

        def cond(s: DistDecompCarry):
            return (s.b_lo > s.b_hi + 2.0 * epsilon) & (s.n_iter < limit)

        def body(s: DistDecompCarry):
            return _dist_decomp_step(
                s, xs, ys, x2s, valid, c=c, kspec=kspec,
                n_per_shard=n_per_shard, n_true=n_true, q=q,
                inner_cap=inner_cap, epsilon=epsilon, limit=limit,
                shard_x=shard_x, precision=precision, weights=weights,
                pairwise_clip=pairwise_clip)

        carry = carry._replace(
            b_hi=pcast_varying(carry.b_hi),
            b_lo=pcast_varying(carry.b_lo),
            n_iter=pcast_varying(carry.n_iter),
            rounds=pcast_varying(carry.rounds))
        out = lax.while_loop(cond, body, carry)
        # Pre-pmax per-shard probe for the desync detector
        # (parallel/mesh.shard_probe, resilience/elastic.py).
        probe = shard_probe(out.n_iter, out.b_lo, out.b_hi)
        return out._replace(b_hi=lax.pmax(out.b_hi, SHARD_AXIS),
                            b_lo=lax.pmax(out.b_lo, SHARD_AXIS),
                            n_iter=lax.pmax(out.n_iter, SHARD_AXIS),
                            rounds=lax.pmax(out.rounds, SHARD_AXIS)), \
            probe

    carry_specs = DistDecompCarry(alpha=P(SHARD_AXIS), f=P(SHARD_AXIS),
                                  b_hi=P(), b_lo=P(), n_iter=P(),
                                  rounds=P())
    mapped = shard_map_compat(
        run, mesh=mesh,
        in_specs=(carry_specs, x_spec, P(SHARD_AXIS), x_spec,
                  P(SHARD_AXIS), P()),
        out_specs=(carry_specs, P(SHARD_AXIS)))

    def run_with_stats(carry, xs, ys, x2s, valid, limit):
        final, probe = mapped(carry, xs, ys, x2s, valid, limit)
        return final, jnp.concatenate([
            pack_stats(final.n_iter, final.b_lo, final.b_hi,
                       n_sv=device_sv_count(final.alpha),
                       rounds=final.rounds), probe])

    return jax.jit(run_with_stats, donate_argnums=(0,))


def train_distributed_decomp(x: np.ndarray, y: np.ndarray,
                             config: SVMConfig,
                             mesh: Optional[jax.sharding.Mesh] = None,
                             f_init: Optional[np.ndarray] = None,
                             alpha_init: Optional[np.ndarray] = None
                             ) -> TrainResult:
    """working_set > 2 over a device mesh; NumPy in/out like the rest."""
    config.validate()
    n, d = x.shape
    if mesh is None:
        mesh = make_data_mesh(config.shards)
    gamma = float(config.resolve_gamma(d))
    kspec = config.kernel_spec(d)
    eps = float(config.epsilon)
    q = 2 * min(int(config.working_set) // 2, n)

    ckpt = resume_state(config, n, d, gamma, shards=mesh.devices.size)
    di = prepare_distributed_inputs(x, y, config, mesh, ckpt,
                                    f_init, alpha_init)
    n_s = di.n_s
    xd, yd, x2, validd = di.xd, di.yd, di.x2, di.validd
    shard, repl, init = di.shard, di.repl, di.init

    carry = DistDecompCarry(
        alpha=jax.device_put(np.asarray(init[0], np.float32), shard),
        f=jax.device_put(np.asarray(init[1], np.float32), shard),
        b_hi=jax.device_put(np.float32(init[2]), repl),
        b_lo=jax.device_put(np.float32(init[3]), repl),
        n_iter=jax.device_put(np.int32(init[4]), repl),
        rounds=jax.device_put(np.int32(0), repl))

    def build(q_now: int):
        q_now = 2 * min(int(q_now) // 2, n)     # same clamp as above
        cap = int(config.inner_iters) or max(32, q_now // 4)
        # Per-q program name, like the single-device decomp path: the
        # trace shows which regrow paid the recompile.
        r = compilewatch.instrument(
            _build_dist_decomp_runner(
                mesh, float(config.c), kspec, eps, n_s, q_now, cap,
                bool(config.shard_x), config.matmul_precision.upper(),
                (float(config.weight_pos), float(config.weight_neg)),
                config.clip == "pairwise"),
            f"dist-decomp-chunk/q={q_now}")

        def step(cr, lim):
            limit = jax.device_put(np.int32(lim), repl)
            return r(cr, xd, yd, x2, validd, limit)

        return step

    # Adaptive growth works unchanged over the mesh: the sharded carry
    # is program-independent too (alpha/f are (n_s,)-per-shard whatever
    # q is), so a growth rebuild is just a new SPMD program; the SV
    # count gathers the sharded alpha (padding rows hold alpha=0 and
    # count as non-SV).
    if config.grow_working_set:
        from dpsvm_tpu.solver.decomp import _make_growth_hook
        poll_hook = _make_growth_hook(config, n, q, build)
    else:
        poll_hook = None

    def carry_from_ckpt(ck):
        # Divergence-rollback hook (docs/ROBUSTNESS.md): sharded carry
        # from checkpoint state, rounds counter restarting at 0
        # (telemetry, not solver state).
        a0 = np.zeros((n_s * mesh.devices.size,), np.float32)
        a0[:n] = np.asarray(ck.alpha, np.float32)
        f0 = np.zeros((n_s * mesh.devices.size,), np.float32)
        f0[:n] = np.asarray(ck.f, np.float32)
        return DistDecompCarry(
            alpha=jax.device_put(a0, shard),
            f=jax.device_put(f0, shard),
            b_hi=jax.device_put(np.float32(ck.b_hi), repl),
            b_lo=jax.device_put(np.float32(ck.b_lo), repl),
            n_iter=jax.device_put(np.int32(ck.n_iter), repl),
            rounds=jax.device_put(np.int32(0), repl))

    return host_training_loop(
        config, gamma, n, d, carry,
        step_chunk=build(q),
        carry_to_host=lambda cr: (to_host(cr.alpha)[:n],
                                  to_host(cr.f)[:n]),
        it0=int(init[4]),
        poll_hook=poll_hook,
        carry_from_ckpt=carry_from_ckpt,
        shards=mesh.devices.size,
    )

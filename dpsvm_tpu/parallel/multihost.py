"""Multi-host (multi-process) initialization.

The reference scales across machines with `mpirun --hostfile hf` and MPI
process management (``svmTrainMain.cpp:144-159``, ``Makefile:74``). The
JAX-native equivalent is one process per host calling
``jax.distributed.initialize`` before any device use; afterwards
``jax.devices()`` spans every host's chips, the data mesh covers the full
slice/pod, and the SAME shard_map program runs unchanged — per-iteration
collectives ride ICI within a slice and DCN across slices. There is no
MPI anywhere.

Typical launch (one command per host, or via your cluster scheduler):

    python -c "import dpsvm_tpu.parallel.multihost as mh; \
               mh.initialize(coordinator='host0:8476', num_processes=4, \
                             process_id=$RANK)" ...

On Cloud TPU VMs all three arguments are discovered from the metadata
server, so ``initialize()`` with no arguments suffices.
"""

from __future__ import annotations

from typing import Optional

import jax

_initialized = False


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or create) the multi-host runtime. Idempotent.

    Deliberately checks only the local flag, NOT ``is_initialized()``:
    that helper may consult ``jax.process_count()``, and any such call
    initializes the XLA backend — after which
    ``jax.distributed.initialize`` refuses to run at all.
    """
    global _initialized
    if _initialized:
        return
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # Someone else (a launcher) already initialized this process.
        if "already" not in str(e).lower():
            raise
    _initialized = True


def is_initialized() -> bool:
    # jax exposes no public "is the distributed client up" predicate
    # (jax.distributed.global_state is gone in 0.9), so track our own
    # calls and fall back to the observable multi-process signal — but
    # never touch jax.process_count() while the backend is still cold,
    # since that call would itself initialize it (and permanently block
    # a later jax.distributed.initialize in this process).
    if _initialized:
        return True
    try:
        from jax._src import xla_bridge
        if not xla_bridge.backends_are_initialized():
            return False
    except (ImportError, AttributeError):    # private API moved: assume
        pass                                 # warm and fall through
    return jax.process_count() > 1


def process_info() -> str:
    """Rank banner, the reference's Get_rank/Get_processor_name analog
    (``svmTrainMain.cpp:154-167``)."""
    return (f"process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.local_device_count()} local / "
            f"{jax.device_count()} global devices")


def topology() -> dict:
    """Device/process topology facts as one dictionary — consumed by
    ``dpsvm doctor`` (resilience/doctor.py) and useful for logs. Safe
    to call any time after the backend is up; initializes the backend
    if it is not (callers wanting a bounded wait go through
    ``utils.backend_guard.probe_devices`` first)."""
    try:
        devs = jax.devices()
        return {
            "platform": devs[0].platform,
            "global_devices": len(devs),
            "local_devices": jax.local_device_count(),
            "processes": jax.process_count(),
            "process_id": jax.process_index(),
            "device_kinds": sorted({str(getattr(d, "device_kind", "?"))
                                    for d in devs}),
        }
    except Exception as e:               # dead backend: report, not raise
        return {"error": f"{type(e).__name__}: {e}"}

"""Multi-host (multi-process) runtime: lifecycle, identity, launcher.

The reference scales across machines with `mpirun --hostfile hf` and MPI
process management (``svmTrainMain.cpp:144-159``, ``Makefile:74``). The
JAX-native equivalent is one process per host calling
``jax.distributed.initialize`` before any device use; afterwards
``jax.devices()`` spans every host's chips, the data mesh covers the full
slice/pod, and the SAME shard_map program runs unchanged — per-iteration
collectives ride ICI within a slice and DCN across slices. There is no
MPI anywhere.

Typical launch (one command per host, or via your cluster scheduler):

    dpsvm train --coordinator host0:8476 --num-hosts 4 --host-id $RANK \
                --shards 16 ...

On Cloud TPU VMs all three arguments are discovered from the metadata
server, so ``initialize()`` with no arguments suffices there.

CI story (docs/DISTRIBUTED.md "Multi-host"): the whole lifecycle is
testable on CPU — N single-device "host" subprocesses on localhost, a
free coordinator port, and XLA's gloo CPU collectives (the default CPU
client cannot run cross-process computations at all; ``initialize``
flips the collectives implementation BEFORE the distributed client
comes up, which is the only moment it can be flipped). The host-group
supervisor that spawns/monitors/reforms such groups lives in
``resilience/hostgroup.py``.
"""

from __future__ import annotations

import os
import socket
from typing import Dict, Optional

import numpy as np

_initialized = False
_host_count = 1
_host_id = 0


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Join (or create) the multi-host runtime. Idempotent.

    Deliberately checks only the local flag, NOT ``is_initialized()``:
    that helper may consult ``jax.process_count()``, and any such call
    initializes the XLA backend — after which
    ``jax.distributed.initialize`` refuses to run at all. For the same
    reason the CLI calls this BEFORE its backend probe
    (cli.main -> _init_backend).
    """
    global _initialized, _host_count, _host_id
    if _initialized:
        return
    import jax

    # The stock CPU client has no cross-process collectives ("Multiprocess
    # computations aren't implemented on the CPU backend"); gloo does.
    # Must be set before the distributed client exists — harmless for
    # TPU/GPU backends (the knob only selects the CPU client's
    # implementation) and absent in very old jaxlibs (guarded).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        pass
    kwargs = {}
    if coordinator is not None:
        kwargs["coordinator_address"] = coordinator
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # Someone else (a launcher) already initialized this process.
        if "already" not in str(e).lower():
            raise
    _initialized = True
    if num_processes is not None:
        _host_count = int(num_processes)
        _host_id = int(process_id or 0)
    else:
        # Auto-detected (TPU metadata server): the backend is up now,
        # so the process facts are a dictionary read.
        _host_count = jax.process_count()
        _host_id = jax.process_index()


def is_initialized() -> bool:
    # jax exposes no public "is the distributed client up" predicate
    # (jax.distributed.global_state is gone in 0.9), so track our own
    # calls and fall back to the observable multi-process signal — but
    # never touch jax.process_count() while the backend is still cold,
    # since that call would itself initialize it (and permanently block
    # a later jax.distributed.initialize in this process).
    if _initialized:
        return True
    try:
        from jax._src import xla_bridge
        if not xla_bridge.backends_are_initialized():
            return False
    except (ImportError, AttributeError):    # private API moved: assume
        pass                                 # warm and fall through
    import jax
    return jax.process_count() > 1


def host_count() -> int:
    """Hosts in the group. 1 on an uninitialized single process —
    read from the recorded lifecycle, NEVER from a jax call, so it is
    safe at any time (including before the backend is warm)."""
    return _host_count if _initialized else 1


def host_id() -> int:
    """This process's rank in the group (0 on an uninitialized single
    process). Same cold-backend safety contract as ``host_count``."""
    return _host_id if _initialized else 0


def host_allgather(value) -> np.ndarray:
    """Stack ``value`` across hosts -> ``(host_count, ...)`` ndarray.

    On an uninitialized single process this is a pure-NumPy identity
    wrap — shape ``(1, ...)`` — touching no jax state at all (pinned by
    tests/test_multihost.py: today's only mode must stay bit-identical).
    Under a multi-host runtime it is a real cross-process allgather
    (every host must call it — it is a collective)."""
    if not _initialized:
        return np.asarray(value)[None]
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(
        jnp.asarray(value), tiled=False))


def process_info() -> str:
    """Rank banner, the reference's Get_rank/Get_processor_name analog
    (``svmTrainMain.cpp:154-167``)."""
    import jax
    return (f"process {jax.process_index()}/{jax.process_count()}, "
            f"{jax.local_device_count()} local / "
            f"{jax.device_count()} global devices")


def topology() -> dict:
    """Device/process topology facts as one dictionary — consumed by
    ``dpsvm doctor`` (resilience/doctor.py) and useful for logs. Safe
    to call any time after the backend is up; initializes the backend
    if it is not (callers wanting a bounded wait go through
    ``utils.backend_guard.probe_devices`` first)."""
    import jax
    try:
        devs = jax.devices()
        return {
            "platform": devs[0].platform,
            "global_devices": len(devs),
            "local_devices": jax.local_device_count(),
            "processes": jax.process_count(),
            "process_id": jax.process_index(),
            "device_kinds": sorted({str(getattr(d, "device_kind", "?"))
                                    for d in devs}),
        }
    except Exception as e:               # dead backend: report, not raise
        return {"error": f"{type(e).__name__}: {e}"}


# ---------------------------------------------------------------------
# Localhost CPU host-group launch plumbing (CI / the host-loss drill).

def find_free_port() -> int:
    """A free localhost TCP port for the coordinator (bind-to-0 probe;
    the tiny race between close and the coordinator's own bind is
    acceptable for drills — a clash fails loudly and a retry picks a
    fresh port)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def coordinator_reachable(coordinator: str,
                          timeout_s: float = 5.0) -> Optional[str]:
    """None when a TCP connect to ``host:port`` succeeds within the
    deadline; else the one-line reason. Used by ``dpsvm doctor`` — a
    pure socket probe that never touches jax (reporting must not warm
    a backend the process may still want to distributed-initialize)."""
    host, sep, port = coordinator.rpartition(":")
    if not sep or not port.isdigit():
        return f"malformed coordinator address {coordinator!r} (want host:port)"
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=timeout_s):
            return None
    except OSError as e:
        return (f"coordinator {coordinator} unreachable within "
                f"{timeout_s:g}s ({e})")


def local_host_env(host_id: int, base: Optional[Dict[str, str]] = None
                   ) -> Dict[str, str]:
    """Environment for one localhost CPU "host" subprocess: CPU
    platform pinned, and the virtual-device multiplier stripped from
    XLA_FLAGS so each host owns exactly ONE device (the whole point of
    the drill is a real cross-process mesh, not one process pretending
    to be eight)."""
    env = dict(os.environ if base is None else base)
    env["JAX_PLATFORMS"] = "cpu"
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "xla_force_host_platform_device_count" not in f]
    flags.append("--xla_force_host_platform_device_count=1")
    env["XLA_FLAGS"] = " ".join(flags)
    env["DPSVM_HOST_ID"] = str(int(host_id))
    return env

"""Device-mesh construction for the 1-D data-parallel axis.

The reference's process topology is ``mpirun -np P`` + a hostfile of
one-GPU nodes (``hf:1-11``, ``Makefile:74``), with cluster size fixed at
``MPI::COMM_WORLD.Get_size()`` (``svmTrainMain.cpp:153``). The TPU-native
equivalent is a 1-D ``jax.sharding.Mesh`` over axis ``"shard"``: within a
slice the per-iteration collectives ride ICI; across hosts/slices JAX's
runtime routes them over DCN after ``jax.distributed.initialize`` (which
multi-host launchers call before building the mesh — same SPMD program
either way).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

SHARD_AXIS = "shard"


def shard_map_compat(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` across the jax versions this repo meets.

    Newer jax exposes ``jax.shard_map`` with varying-manual-axes (VMA)
    typing; 0.4.x only has ``jax.experimental.shard_map.shard_map``,
    whose ``check_rep`` replication checker cannot see through the
    trainers' while_loop-carried all_gather values — so it is disabled
    there (the values are replicated-equal by construction, which the
    newer VMA path proves with pcast/pmax instead). Both distributed
    trainers and the shrinking manager's SPMD rebuilds funnel through
    here."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def pcast_varying(v):
    """``lax.pcast(v, (SHARD_AXIS,), to="varying")`` where VMA typing
    exists; identity where it does not (jax 0.4.x: no pcast, and no
    typing to satisfy). Already-varying leaves pass through (pcast
    rejects them — the dist-decomp subsolve seed mixes psum-derived and
    invariant values)."""
    if not hasattr(lax, "pcast"):
        return v
    try:
        return lax.pcast(v, (SHARD_AXIS,), to="varying")
    except ValueError:
        return v


def shard_probe(n_iter, b_lo, b_hi):
    """This shard's (3,) i32 probe: [n_iter, b_lo bits, b_hi bits].

    Emitted PER SHARD (out_spec ``P(SHARD_AXIS)``) by both SPMD chunk
    runners and appended to the packed-stats array, so the host reads
    every shard's own view of the replicated-by-construction poll
    scalars in the SAME single D2H transfer. Disagreement between rows
    is a desynchronized mesh (resilience/elastic.py). Floats ride as
    exact bit patterns, like the replicated stats lanes
    (solver/driver.pack_stats). Called on the PRE-pmax loop outputs —
    the pmax fold would erase exactly the per-shard disagreement this
    probe exists to expose."""
    bits = lax.bitcast_convert_type(
        jnp.stack([jnp.asarray(b_lo, jnp.float32),
                   jnp.asarray(b_hi, jnp.float32)]), jnp.int32)
    head = jnp.reshape(
        pcast_varying(jnp.asarray(n_iter, jnp.int32)), (1,))
    return jnp.concatenate([head, bits])


def to_host(arr) -> np.ndarray:
    """Global-array -> host NumPy, multi-host-safe.

    On one process ``np.asarray`` suffices. Under a multi-process
    runtime a sharded global array is not fully addressable — each host
    holds only its shard — so the full array is assembled with an
    all-gather across processes (the standard jax multihost_utils
    path). Both distributed trainers funnel their final (alpha, f)
    read-back through here."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def make_data_mesh(shards: int,
                   devices: Optional[Sequence[jax.Device]] = None
                   ) -> jax.sharding.Mesh:
    """A 1-D mesh of ``shards`` devices along axis ``"shard"``."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < shards:
        raise ValueError(
            f"need {shards} devices for {shards} shards, have {len(devices)} "
            f"({[d.platform for d in devices[:4]]}...). For CPU-simulated "
            f"meshes set XLA_FLAGS=--xla_force_host_platform_device_count=N.")
    return jax.make_mesh((shards,), (SHARD_AXIS,),
                         devices=list(devices)[:shards])

"""Device-mesh construction for the 1-D data-parallel axis.

The reference's process topology is ``mpirun -np P`` + a hostfile of
one-GPU nodes (``hf:1-11``, ``Makefile:74``), with cluster size fixed at
``MPI::COMM_WORLD.Get_size()`` (``svmTrainMain.cpp:153``). The TPU-native
equivalent is a 1-D ``jax.sharding.Mesh`` over axis ``"shard"``: within a
slice the per-iteration collectives ride ICI; across hosts/slices JAX's
runtime routes them over DCN after ``jax.distributed.initialize`` (which
multi-host launchers call before building the mesh — same SPMD program
either way).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

SHARD_AXIS = "shard"


def to_host(arr) -> np.ndarray:
    """Global-array -> host NumPy, multi-host-safe.

    On one process ``np.asarray`` suffices. Under a multi-process
    runtime a sharded global array is not fully addressable — each host
    holds only its shard — so the full array is assembled with an
    all-gather across processes (the standard jax multihost_utils
    path). Both distributed trainers funnel their final (alpha, f)
    read-back through here."""
    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(arr)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(arr, tiled=True))


def make_data_mesh(shards: int,
                   devices: Optional[Sequence[jax.Device]] = None
                   ) -> jax.sharding.Mesh:
    """A 1-D mesh of ``shards`` devices along axis ``"shard"``."""
    if devices is None:
        devices = jax.devices()
    if len(devices) < shards:
        raise ValueError(
            f"need {shards} devices for {shards} shards, have {len(devices)} "
            f"({[d.platform for d in devices[:4]]}...). For CPU-simulated "
            f"meshes set XLA_FLAGS=--xla_force_host_platform_device_count=N.")
    return jax.make_mesh((shards,), (SHARD_AXIS,),
                         devices=list(devices)[:shards])

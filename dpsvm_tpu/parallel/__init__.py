"""Distributed execution: device mesh helpers + shard_map SMO solver."""

from dpsvm_tpu.parallel.mesh import make_data_mesh
from dpsvm_tpu.parallel.dist_smo import train_distributed

__all__ = ["make_data_mesh", "train_distributed"]

"""Library-first training entry point.

The reference has no framework API — each binary's ``main()`` drives the
solver directly (SURVEY §1). Here ``train`` is the single entry point;
CLIs are thin wrappers over it. Dispatch: ``config.shards == 1`` runs the
single-device solver, ``> 1`` the shard_map solver over a 1-D device mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.svm import SVMModel


def _check_xy(x, y):
    """The cheap shape/label validation shared by train and warm_start
    (warm_start must run it BEFORE its O(n^2) kernel pass)."""
    from dpsvm_tpu.utils import densify

    x = np.asarray(densify(x), np.float32)
    y = np.asarray(y)
    if x.ndim != 2:
        raise ValueError(f"x must be (n, d), got shape {x.shape}")
    if y.shape != (x.shape[0],):
        raise ValueError(f"y must be ({x.shape[0]},), got {y.shape}")
    labels = np.unique(y)
    if not np.all(np.isin(labels, (-1, 1))):
        raise ValueError(
            f"labels must be +/-1 for binary training, got {labels[:10]} — "
            "for multi-class data use models.multiclass.train_multiclass "
            "(CLI: train --multiclass)")
    return x, y


def train(x: np.ndarray, y: np.ndarray,
          config: Optional[SVMConfig] = None,
          f_init: Optional[np.ndarray] = None,
          alpha_init: Optional[np.ndarray] = None,
          guard_eta: bool = False) -> TrainResult:
    """Train a binary SVM with the modified-SMO solver.

    x: (n, d) float features; y: (n,) labels in {+1, -1}.
    ``f_init`` overrides the f = -y initialization (the SVR wrapper's
    hook — users train regressors through models.svr.train_svr).
    ``guard_eta`` clamps the update denominator to LIBSVM's TAU (1e-12)
    on the first-order path. The SVR/one-class wrappers set it (their
    duplicated rows make eta == 0 reachable); it stays off by default so
    plain classification — including warm_start continuations — keeps
    the reference's raw division bit-for-bit (svmTrainMain.cpp:289).
    """
    config = config or SVMConfig()
    config.validate()
    if config.solver != "exact":
        raise ValueError(
            "approx solvers have no dual alpha vector to return, and "
            "the cascade is a multi-stage schedule — train through "
            "api.fit (which returns the right model kind), or "
            "approx.fit_approx / solver.cascade.fit_cascade directly")
    x, y = _check_xy(x, y)
    # Concretize any "auto" solver-path sentinels now that the problem
    # shape is known; every path below sees only concrete values.
    config = config.resolved(x.shape[0], x.shape[1])
    if config.kernel == "precomputed" and x.shape[0] != x.shape[1]:
        raise ValueError("precomputed kernel training needs the square "
                         f"(n, n) kernel matrix as x, got {x.shape}")
    if config.polish:
        # Two-phase "polishing" (the fast-SVM recipe, arXiv:2207.01016):
        # the configured solver path does the bulk of the work at fast
        # precision, then an exact-f32 warm start refines to the same
        # epsilon. The refinement recomputes f from alpha exactly, so
        # the final KKT condition holds in exact arithmetic while the
        # expensive trajectory ran on the MXU's bf16 path.
        import dataclasses
        import time
        import warnings

        if f_init is not None or alpha_init is not None:
            raise ValueError(
                "polish composes with the plain classification init "
                "only — the SVR/one-class wrappers seed f and manage "
                "their own duals; polish their output via warm_start "
                "with matmul_precision='highest' instead")
        fast_p = ("default" if config.matmul_precision == "highest"
                  else config.matmul_precision)
        fast = train(x, y, dataclasses.replace(
            config, polish=False, matmul_precision=fast_p),
            guard_eta=guard_eta)
        budget = config.max_iter - fast.n_iter
        if budget <= 0:
            if fast.converged:
                warnings.warn(
                    "polish: the fast phase consumed the entire "
                    "max_iter budget while converging, so the exact-f32 "
                    "refinement was skipped — the returned model's KKT "
                    "condition holds at fast precision only. Raise "
                    "max_iter to get the polished guarantee.")
            return fast
        t0 = time.perf_counter()
        refined = warm_start(x, y, fast.alpha, dataclasses.replace(
            config, polish=False, matmul_precision="highest",
            max_iter=budget), guard_eta=guard_eta)
        # Wall-clock the whole refinement call: warm_start's fresh
        # O(n^2) kernel pass is intrinsic to the schedule, not overhead
        # to hide from train_seconds.
        refine_seconds = time.perf_counter() - t0
        return dataclasses.replace(
            refined, n_iter=fast.n_iter + refined.n_iter,
            train_seconds=fast.train_seconds + refine_seconds)
    if config.backend == "numpy":
        from dpsvm_tpu.solver.oracle import smo_reference
        return smo_reference(x, y, config, f_init=f_init,
                             alpha_init=alpha_init, guard_eta=guard_eta)
    if config.shrinking:
        # Active-set training (solver/shrink.py); composes with
        # working_set > 2 AND shards > 1 (the manager wraps any of the
        # compiled chunk runners, local or SPMD).
        from dpsvm_tpu.solver.shrink import train_shrinking
        return train_shrinking(
            x, y, config, f_init=f_init, alpha_init=alpha_init,
            guard_eta=guard_eta)
    if config.shards > 1:
        if config.working_set > 2:
            from dpsvm_tpu.parallel.dist_decomp import (
                train_distributed_decomp)
            return train_distributed_decomp(x, y, config, f_init=f_init,
                                            alpha_init=alpha_init)
        from dpsvm_tpu.parallel.dist_smo import train_distributed
        return train_distributed(x, y, config, f_init=f_init,
                                 alpha_init=alpha_init, guard_eta=guard_eta)
    if config.working_set > 2:
        # Large-working-set decomposition (solver/decomp.py). Eta is
        # always TAU-clamped there, so guard_eta is subsumed.
        from dpsvm_tpu.solver.decomp import train_single_device_decomp
        return train_single_device_decomp(x, y, config, f_init=f_init,
                                          alpha_init=alpha_init)
    from dpsvm_tpu.experimental.fused import (train_single_device_fused,
                                               use_fused)
    if f_init is None and alpha_init is None and use_fused(config):
        # the fused kernel hard-codes the classification init
        return train_single_device_fused(x, y, config)
    from dpsvm_tpu.solver.smo import train_single_device
    return train_single_device(x, y, config, f_init=f_init,
                               alpha_init=alpha_init, guard_eta=guard_eta)


def fit(x: np.ndarray, y: np.ndarray,
        config: Optional[SVMConfig] = None) -> Tuple[SVMModel, TrainResult]:
    """train + SV compaction in one call.

    ``config.solver = "approx-rff" | "approx-nystrom"`` dispatches to
    the kernel-approximation subsystem (docs/APPROX.md) and returns an
    ``ApproxSVMModel`` instead — same (model, result) contract, and
    every downstream consumer (``models/svm.decision_function``,
    ``models/io``, the serving engine, CV, multiclass) dispatches on
    the model kind.

    ``config.solver = "cascade"`` dispatches to the three-stage
    approx-warm-start -> SV-screening -> exact-dual-polish schedule
    (docs/APPROX.md "Cascade") and returns an ordinary ``SVMModel``
    whose decision function matches a full exact solve."""
    config = config or SVMConfig()
    if config.solver == "cascade":
        from dpsvm_tpu.solver.cascade import fit_cascade
        return fit_cascade(x, y, config)
    if config.solver != "exact":
        from dpsvm_tpu.approx.primal import fit_approx
        return fit_approx(x, y, config)
    from dpsvm_tpu.utils import densify

    x = densify(x)      # from_train_result consumes x too
    result = train(x, y, config)
    return SVMModel.from_train_result(x, y, result), result


def sweep_c(x: np.ndarray, y: np.ndarray, cs,
            config: Optional[SVMConfig] = None,
            gammas=None) -> "list[Tuple[SVMModel, TrainResult]]":
    """Fit the same +/-1 problem at every point of a C (x gamma) grid
    in ONE compiled batched program (solver/batched_ovo.train_c_sweep —
    LIBSVM grid.py's whole grid as one batch: C only moves the box
    bound, gamma only the kernel epilogue after the shared dots).
    Returns [(model, result)] in ``cs`` order (row-major (C, gamma)
    order with ``gammas``); combine with a held-out set or models/cv
    for selection."""
    from dpsvm_tpu.models.svm import SVMModel
    from dpsvm_tpu.solver.batched_ovo import train_c_sweep

    x, y = _check_xy(x, y)
    config = config or SVMConfig()
    if config.solver != "exact":
        raise ValueError("the batched C/gamma sweep is a dual-solver "
                         "program; approx solvers sweep by refitting "
                         "(the feature map is shared work, see "
                         "docs/APPROX.md)")
    results = train_c_sweep(x, y, cs, config, gammas=gammas)
    return [(SVMModel.from_train_result(x, y, r), r) for r in results]


def warm_start(x: np.ndarray, y: np.ndarray, alpha: np.ndarray,
               config: Optional[SVMConfig] = None,
               guard_eta: bool = False) -> TrainResult:
    """Continue training from a previous solution's alpha.

    Recomputes the gradient f = K (alpha*y) - y from scratch in one
    streamed kernel pass and resumes the SMO loop — so a capped run can
    be continued with a larger ``max_iter`` (or a tighter ``epsilon``)
    without a checkpoint file, and an already-converged alpha returns
    after the first convergence poll. Unlike checkpoint resume (which
    replays the incrementally-maintained f for an identical trajectory),
    the fresh f also discards any accumulated float drift.

    The alphas must come from a run with the same C/weights: box-bound
    membership is tested by exact comparison against THIS config's
    bounds, so alphas clipped at a different C are treated as interior.
    """
    from dpsvm_tpu.ops.diagnostics import _stream_kv

    config = config or SVMConfig()
    config.validate()
    if config.solver != "exact":
        raise ValueError("warm_start continues a DUAL trajectory from "
                         "alpha; approx solvers have no dual, and the "
                         "cascade CALLS warm_start for its polish stage "
                         "— pass solver='exact' (resume a primal run "
                         "via checkpoint_path/resume_from instead)")
    if config.polish:
        raise ValueError("warm_start IS the refinement mechanism polish "
                         "is built from — call it with "
                         "matmul_precision='highest' instead of "
                         "polish=True")
    if config.resume_from:
        raise ValueError("config.resume_from would override the given "
                         "alpha (checkpoint resume takes precedence in "
                         "the solvers) — clear it, or resume the "
                         "checkpoint via train() instead")
    x, y = _check_xy(x, y)
    yf = np.asarray(y, np.float32)
    alpha = np.asarray(alpha, np.float32)
    if alpha.shape != (x.shape[0],):
        raise ValueError(f"alpha must be ({x.shape[0]},), got {alpha.shape}")
    box = np.broadcast_to(np.asarray(config.box_bound(y), np.float32),
                          alpha.shape)
    if (not np.isfinite(alpha).all() or (alpha < 0).any()
            or (alpha > box).any()):
        raise ValueError("alpha outside [0, C] (or non-finite) — not a "
                         "feasible dual point for this config")
    spec = config.kernel_spec(x.shape[1])
    kv = _stream_kv(x, alpha * yf, spec, block=4096)
    return train(x, y, config, f_init=(kv - yf).astype(np.float32),
                 alpha_init=alpha, guard_eta=guard_eta)

"""Library-first training entry point.

The reference has no framework API — each binary's ``main()`` drives the
solver directly (SURVEY §1). Here ``train`` is the single entry point;
CLIs are thin wrappers over it. Dispatch: ``config.shards == 1`` runs the
single-device solver, ``> 1`` the shard_map solver over a 1-D device mesh.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from dpsvm_tpu.config import SVMConfig, TrainResult
from dpsvm_tpu.models.svm import SVMModel


def train(x: np.ndarray, y: np.ndarray,
          config: Optional[SVMConfig] = None,
          f_init: Optional[np.ndarray] = None,
          alpha_init: Optional[np.ndarray] = None) -> TrainResult:
    """Train a binary SVM with the modified-SMO solver.

    x: (n, d) float features; y: (n,) labels in {+1, -1}.
    ``f_init`` overrides the f = -y initialization (the SVR wrapper's
    hook — users train regressors through models.svr.train_svr).
    """
    config = config or SVMConfig()
    config.validate()
    x = np.asarray(x, np.float32)
    y = np.asarray(y)
    if x.ndim != 2:
        raise ValueError(f"x must be (n, d), got shape {x.shape}")
    if y.shape != (x.shape[0],):
        raise ValueError(f"y must be ({x.shape[0]},), got {y.shape}")
    labels = np.unique(y)
    if not np.all(np.isin(labels, (-1, 1))):
        raise ValueError(
            f"labels must be +/-1 for binary training, got {labels[:10]} — "
            "for multi-class data use models.multiclass.train_multiclass "
            "(CLI: train --multiclass)")
    if config.backend == "numpy":
        from dpsvm_tpu.solver.oracle import smo_reference
        return smo_reference(x, y, config, f_init=f_init,
                             alpha_init=alpha_init)
    if config.shards > 1:
        from dpsvm_tpu.parallel.dist_smo import train_distributed
        return train_distributed(x, y, config, f_init=f_init,
                                 alpha_init=alpha_init)
    from dpsvm_tpu.solver.fused import train_single_device_fused, use_fused
    if f_init is None and alpha_init is None and use_fused(config):
        # the fused kernel hard-codes the classification init
        return train_single_device_fused(x, y, config)
    from dpsvm_tpu.solver.smo import train_single_device
    return train_single_device(x, y, config, f_init=f_init,
                               alpha_init=alpha_init)


def fit(x: np.ndarray, y: np.ndarray,
        config: Optional[SVMConfig] = None) -> Tuple[SVMModel, TrainResult]:
    """train + SV compaction in one call."""
    result = train(x, y, config)
    return SVMModel.from_train_result(x, y, result), result

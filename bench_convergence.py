"""Run the reference's north-star job TO CONVERGENCE and report wall-clock.

The reference's published benchmark is MNIST even-odd (60000 x 784, RBF
C=10 gamma=0.25 eps=1e-3) trained to convergence: 137 s on one GTX 780,
46 s on its 10-GPU cluster (/root/reference/README.md:23). ``bench.py``
measures steady-state iteration throughput; THIS harness measures the
actual deliverable — seconds to convergence, iterations, and the final
SV count — for a single run configuration.

Prints ONE JSON line:
    {"metric": "mnist_scale_seconds_to_convergence", "value": <s>,
     "unit": "s", "vs_baseline": <46 / s>,
     "n_iter": ..., "n_sv": ..., "converged": ..., "precision": ...,
     "train_accuracy": ...}

``vs_baseline`` > 1 means faster than the reference's 10-GPU cluster.

Environment:
    BENCH_PRECISION   DEFAULT (bf16-multiply MXU, the headline) | HIGHEST
    BENCH_DATA        path to a real train CSV (label,f1,...,fd). When
                      unset, uses the synthetic MNIST-shaped stand-in.
    BENCH_N/BENCH_D   synthetic shape override  (default 60000 x 784)
    BENCH_C/BENCH_GAMMA/BENCH_EPS/BENCH_MAX_ITER
                      hyperparameters (default 10 / 0.25 / 1e-3 /
                      400000; the reference's own budget is 100000 and
                      its real MNIST converged at ~100k iterations —
                      the planted stand-in is slightly harder, 143k)
    BENCH_SELECTION   first-order (reference parity) | second-order
    BENCH_WORKING_SET 2 (classic pair SMO) | even q > 2 (large-working-
                      set decomposition, solver/decomp.py)
    BENCH_INNER_ITERS decomposition inner-step cap (0 = auto q/4)
    BENCH_GROW        1 = adaptive working-set growth (grow_working_set;
                      only with BENCH_WORKING_SET > 2)
    BENCH_SHRINKING   1 = LIBSVM-style active-set training
                      (solver/shrink.py; composes with the above)
    BENCH_PALLAS      auto (default) | on — 'on' with BENCH_WORKING_SET
                      selects the Pallas inner-subsolve kernel
    BENCH_TRACE_OUT   write the run-telemetry trace here
                      (docs/OBSERVABILITY.md; unset = no trace. The
                      burst runner sets this per sweep tag so every
                      recorded row carries its provenance trace.)
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_SECONDS = 46.0     # reference 10-GPU cluster (README.md:23)


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def convergence_run(x, y, config) -> dict:
    """Train (x, y) under ``config`` and return the measurement dict
    that main() prints as its JSON line. Importable so the one-process
    window runner (benchmarks/burst_runner.py) produces rows with
    exactly this schema without paying a subprocess per tag."""
    from dpsvm_tpu.api import train
    from dpsvm_tpu.models.svm import SVMModel, evaluate

    t0 = time.perf_counter()
    result = train(x, y, config)
    seconds = time.perf_counter() - t0

    model = SVMModel.from_train_result(x, y, result)
    acc = evaluate(model, x, y)
    log(f"{result.n_iter} iters in {seconds:.2f}s, converged="
        f"{result.converged}, n_sv={result.n_sv}, train_acc={acc:.4f}")
    log(f"split: loop {result.train_seconds:.2f}s (chunk runner, compile "
        f"included) + setup {seconds - result.train_seconds:.2f}s "
        f"(H2D transfer, host norms, alpha readback)")

    # Device-side facts from the run's own trace (docs/OBSERVABILITY.md):
    # the driver records compile/HBM/FLOP observations into trace_out,
    # so the result row carries compile overhead, not just it/s. Null
    # when tracing is off or the trace is unreadable — a provenance
    # hiccup must not burn a measured row.
    facts = {}
    if getattr(config, "trace_out", None):
        try:
            from dpsvm_tpu.telemetry import load_trace, trace_facts
            facts = trace_facts(load_trace(config.trace_out))
        except (OSError, ValueError) as e:
            log(f"WARNING: trace facts unavailable ({e})")

    return {
        "metric": "mnist_scale_seconds_to_convergence",
        "value": round(seconds, 2),
        "unit": "s",
        "vs_baseline": round(BASELINE_SECONDS / seconds, 3)
        if seconds > 0 else 0.0,
        "n_iter": int(result.n_iter),
        "n_sv": int(result.n_sv),
        "converged": bool(result.converged),
        "precision": config.matmul_precision,
        "selection": config.selection,
        "working_set": config.working_set,
        "shrinking": config.shrinking,
        "polish": config.polish,
        "train_accuracy": round(float(acc), 6),
        "n_compiles": facts.get("n_compiles"),
        "compile_seconds": facts.get("compile_seconds"),
        "hbm_peak": facts.get("hbm_peak"),
        "est_flops": facts.get("est_flops"),
        "est_bytes": facts.get("est_bytes"),
        # achieved/peak FLOP/s vs the per-backend peak table
        # (observability/roofline.py) — null on CPU/unknown hardware,
        # gateable via `dpsvm perf gate --metric roofline_fraction`
        "roofline_fraction": facts.get("roofline_fraction"),
    }


def main() -> None:
    from dpsvm_tpu.utils.backend_guard import require_devices

    dev = require_devices()[0]
    log(f"device: {dev} ({dev.platform})")

    from dpsvm_tpu.utils.backend_guard import enable_compile_cache
    enable_compile_cache()

    from dpsvm_tpu.config import SVMConfig

    precision = os.environ.get("BENCH_PRECISION", "DEFAULT").lower()
    selection = os.environ.get("BENCH_SELECTION", "first-order")
    c = float(os.environ.get("BENCH_C", 10.0))
    gamma = float(os.environ.get("BENCH_GAMMA", 0.25))
    eps = float(os.environ.get("BENCH_EPS", 1e-3))
    max_iter = int(os.environ.get("BENCH_MAX_ITER", 400_000))

    data = os.environ.get("BENCH_DATA")
    if data:
        from dpsvm_tpu.data.loader import load_dataset
        x, y = load_dataset(data, None, None)
        log(f"data: {data} ({x.shape[0]}x{x.shape[1]})")
    else:
        from bench_common import standin
        n = int(os.environ.get("BENCH_N", 60_000))
        d = int(os.environ.get("BENCH_D", 784))
        x, y = standin(n=n, d=d, gamma=gamma, seed=0)
    # Host data gen at the big shapes takes real time; don't let it eat
    # the stall watchdog's window for the H2D transfer + first compile.
    from dpsvm_tpu.utils import watchdog
    watchdog.pet()

    # Large chunks cost nothing (the device-side while_loop exits the
    # moment the gap closes — the limit is only a host-poll cadence) and
    # each poll round pays a ~65 ms tunnel round-trip, so poll rarely.
    working_set = int(os.environ.get("BENCH_WORKING_SET", 2))
    inner_iters = int(os.environ.get("BENCH_INNER_ITERS", 0))
    grow = os.environ.get("BENCH_GROW", "") == "1"
    shrinking = os.environ.get("BENCH_SHRINKING", "") == "1"
    use_pallas = os.environ.get("BENCH_PALLAS", "auto")
    # BENCH_VERBOSE=1 prints gap progress at chunk polls — a run killed
    # by an outer wall-clock timeout then still leaves rate evidence on
    # stderr instead of vanishing without a number.
    verbose = os.environ.get("BENCH_VERBOSE", "") == "1"
    polish = os.environ.get("BENCH_POLISH", "") == "1"
    # In-run wall budget (seconds; 0 = none): an over-projected arm
    # returns a partial rate row instead of being timeout-killed with
    # no number (the burst runner sets the config field directly).
    wall_budget = float(os.environ.get("BENCH_WALL_BUDGET", 0) or 0)
    # Run-telemetry trace (docs/OBSERVABILITY.md): rejected by validate
    # with polish (two runs, one file) — drop it there rather than fail
    # a sweep arm over provenance.
    trace_out = os.environ.get("BENCH_TRACE_OUT") or None
    if trace_out and polish:
        log("BENCH_TRACE_OUT ignored: polish is a two-run schedule")
        trace_out = None
    config = SVMConfig(c=c, gamma=gamma, epsilon=eps, max_iter=max_iter,
                       matmul_precision=precision, selection=selection,
                       working_set=working_set, inner_iters=inner_iters,
                       grow_working_set=grow,
                       shrinking=shrinking, use_pallas=use_pallas,
                       polish=polish, verbose=verbose, chunk_iters=8192,
                       wall_budget_s=wall_budget,
                       trace_out=trace_out)

    row = convergence_run(x, y, config)
    print(json.dumps(row), flush=True)
    # Perf-ledger provenance (docs/OBSERVABILITY.md "Perf ledger"):
    # the case tag defaults to the metric name; the burst runner tags
    # its own rows per sweep tag, so standalone runs may pin
    # BENCH_LEDGER_CASE to keep shapes' histories separate.
    from dpsvm_tpu.observability import ledger
    ledger.append(os.environ.get("BENCH_LEDGER_CASE") or row["metric"],
                  row, kind="bench", trace=trace_out,
                  backend=dev.platform)


if __name__ == "__main__":
    main()
